"""Minimal WSGI web framework for the CRUD backends.

Plays the role Flask plays for the reference's crud_backend: routing with
path params, before-request hooks (authn — crud_backend/authn.py:35;
CSRF — csrf.py:91), JSON requests/responses, error handlers mapping
exceptions to JSON bodies (errors/handlers.py), probe routes
(probes.py:8-17), and SPA index serving that refreshes the CSRF cookie
(serving.py:18-31).
"""

from __future__ import annotations

import json
import logging
import os
import re
import traceback

from service_account_auth_improvements_tpu.controlplane.kube import errors
from service_account_auth_improvements_tpu.webapps.core import (
    authn,
    csrf,
    settings,
)

log = logging.getLogger(__name__)

_STATUS_TEXT = {
    200: "OK", 400: "Bad Request", 401: "Unauthorized", 403: "Forbidden",
    404: "Not Found", 405: "Method Not Allowed", 500: "Internal Server Error",
}


class HttpError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class Request:
    def __init__(self, environ: dict, params: dict):
        self.environ = environ
        self.params = params          # path params, e.g. {"namespace": ...}
        self.method = environ["REQUEST_METHOD"]
        self.path = environ.get("PATH_INFO", "")
        self._body = None

    @property
    def query(self) -> dict:
        from urllib.parse import parse_qs
        return {k: v[0] for k, v in
                parse_qs(self.environ.get("QUERY_STRING", "")).items()}

    def header(self, name: str) -> str | None:
        key = "HTTP_" + name.upper().replace("-", "_")
        return self.environ.get(key)

    @property
    def cookies(self) -> dict:
        out = {}
        for part in (self.environ.get("HTTP_COOKIE") or "").split(";"):
            name, _, value = part.strip().partition("=")
            if name:
                out[name] = value
        return out

    @property
    def user(self) -> str | None:
        return authn.get_username(self.environ)

    def json(self) -> dict:
        if self._body is None:
            try:
                length = int(self.environ.get("CONTENT_LENGTH") or 0)
            except ValueError:
                length = 0
            raw = self.environ["wsgi.input"].read(length) if length else b""
            try:
                self._body = json.loads(raw) if raw else {}
            except ValueError:
                raise HttpError(400, "request body is not valid JSON")
        return self._body


class Response:
    def __init__(self, body: bytes, status: int = 200,
                 content_type: str = "application/json"):
        self.body = body
        self.status = status
        self.headers = [("Content-Type", content_type)]

    @classmethod
    def json(cls, payload, status: int = 200) -> "Response":
        return cls(json.dumps(payload).encode(), status)


def _compile(pattern: str):
    """``/api/namespaces/<namespace>/notebooks/<name>`` → regex."""
    regex = re.sub(r"<([a-zA-Z_]+)>", r"(?P<\1>[^/]+)", pattern)
    return re.compile("^" + regex + "$")


def frontend_dirs(app_name: str) -> tuple[str | None, str | None]:
    """(static_dir, shared_static_dir) for an app's checked-in SPA.

    The SPAs live in ``frontends/<app>`` with the shared lib in
    ``frontends/common`` (the reference builds Angular bundles into each
    backend's static dir; ours are plain files needing no build step).
    ``TPUKF_FRONTENDS_DIR`` overrides the root for container images.
    """
    root = os.environ.get("TPUKF_FRONTENDS_DIR")
    if not root:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        root = os.path.join(repo, "frontends")
    app_dir = os.path.join(root, app_name)
    common = os.path.join(root, "common")
    return (app_dir if os.path.isdir(app_dir) else None,
            common if os.path.isdir(common) else None)


class WebApp:
    """App factory product (reference: crud_backend/__init__.py:16).

    Routes + hooks + static SPA serving. Instances are WSGI callables.
    """

    def __init__(self, name: str, static_dir: str | None = None,
                 prefix: str = "/", mode: str | None = None,
                 shared_static_dir: str | None = None):
        self.name = name
        self.static_dir = static_dir
        # requests for common/* assets (the shared frontend lib) fall back
        # here — the analog of kubeflow-common-lib being linked into every
        # app's build (reference jwa_frontend_tests.yaml:33-50)
        self.shared_static_dir = shared_static_dir
        self.prefix = prefix
        self.mode = mode if mode is not None else os.environ.get(
            "BACKEND_MODE", "prod"
        )
        self._routes: list[tuple[str, re.Pattern, object]] = []
        self.add_probe_routes()

    # ------------------------------------------------------------- wiring

    def route(self, method: str, pattern: str):
        def register(fn):
            self._routes.append((method.upper(), _compile(pattern), fn))
            return fn
        return register

    def add_probe_routes(self) -> None:
        @self.route("GET", "/healthz/liveness")
        @authn.no_authentication
        def liveness(req):
            return "alive"

        @self.route("GET", "/healthz/readiness")
        @authn.no_authentication
        def readiness(req):
            return "ready"

    # ------------------------------------------------------------ serving

    def __call__(self, environ, start_response):
        req_path = environ.get("PATH_INFO", "")
        method = environ["REQUEST_METHOD"]
        try:
            for m, regex, fn in self._routes:
                match = regex.match(req_path)
                if match and m == method:
                    req = Request(environ, match.groupdict())
                    self._check_authn(fn, req)
                    self._check_csrf(req)
                    out = fn(req)
                    resp = out if isinstance(out, Response) else \
                        Response.json({
                            "success": True, "status": 200,
                            **(out if isinstance(out, dict) else
                               {"result": out}),
                        })
                    return self._finish(resp, start_response)
            # unmatched API paths must stay JSON 404s — falling through to
            # the SPA index would hand HTML to the JS api() helper
            if (method == "GET" and self.static_dir
                    and not req_path.startswith("/api/")):
                return self._finish(
                    self._serve_static(
                        req_path, environ.get("QUERY_STRING", "")
                    ),
                    start_response,
                )
            raise HttpError(404, f"no route {method} {req_path}")
        except HttpError as e:
            return self._finish(self._error_response(e.code, e.message),
                                start_response)
        except errors.ApiError as e:
            # K8s errors pass through with their code (reference
            # errors/handlers.py maps ApiException the same way).
            return self._finish(
                self._error_response(e.code, str(e)), start_response
            )
        except Exception:
            log.error("unhandled error serving %s %s\n%s", method, req_path,
                      traceback.format_exc())
            return self._finish(
                self._error_response(500, "internal server error"),
                start_response,
            )

    # -------------------------------------------------------------- hooks

    def _check_authn(self, fn, req: Request) -> None:
        """Every route is authenticated unless opted out
        (reference authn.py:35-66)."""
        if settings.dev_mode(self.mode) or settings.disable_auth():
            return
        if getattr(fn, "no_authentication", False):
            return
        if req.user is None:
            raise HttpError(401, "No user detected.")

    def _check_csrf(self, req: Request) -> None:
        if settings.dev_mode(self.mode):
            return
        csrf.check(req)

    # ------------------------------------------------------------- output

    def _error_response(self, code: int, message: str) -> Response:
        return Response.json(
            {"success": False, "status": code, "log": message,
             "user_error": message},
            status=code,
        )

    def _serve_static(self, path: str, query: str = "") -> Response:
        """Hashed assets get long cache; the index serves with a fresh
        CSRF cookie and no-cache (reference serving.py). Unknown deep
        paths redirect to the app root RELATIVELY ("../.." style) so the
        redirect lands correctly under any ingress prefix (/jupyter/...),
        which the backend cannot see — the SPAs are hash-routed, so no
        deep path is meaningful and relative assets would 404 as HTML."""
        rel = path.lstrip("/") or "index.html"
        full = self._safe_join(self.static_dir, rel)
        if (not (full and os.path.isfile(full))
                and rel.startswith("common/") and self.shared_static_dir):
            full = self._safe_join(self.shared_static_dir,
                                   rel[len("common/"):])
        if full and os.path.isfile(full) and rel != "index.html":
            ctype = _content_type(full)
            with open(full, "rb") as f:
                resp = Response(f.read(), content_type=ctype)
            # assets are NOT content-hashed, so the browser must
            # revalidate; only truly hashed names may cache long
            # "hashed" = a ≥6-char hex segment containing a digit
            # (e.g. main.abc123.js), so plain names like app.js revalidate
            cache = ("max-age=31536000, immutable"
                     if re.search(r"\.(?=[0-9a-f]*\d)[0-9a-f]{6,}\.",
                                  os.path.basename(full))
                     else "no-cache")
            resp.headers.append(("Cache-Control", cache))
            return resp
        if rel != "index.html":
            segments = [s for s in path.split("/") if s]
            ups = len(segments) - (0 if path.endswith("/") else 1)
            location = "../" * ups or "./"
            if query:
                location += "?" + query
            resp = Response(b"", status=302, content_type="text/plain")
            resp.headers.append(("Location", location))
            return resp
        index = os.path.join(self.static_dir, "index.html")
        if not os.path.isfile(index):
            raise HttpError(404, "not found")
        with open(index, "rb") as f:
            resp = Response(f.read(), content_type="text/html")
        resp.headers.append(
            ("Cache-Control", "no-cache, no-store, must-revalidate, max-age=0")
        )
        csrf.set_cookie(resp, self.prefix)
        return resp

    @staticmethod
    def _safe_join(root: str, rel: str) -> str:
        """Absolute path under ``root`` or "" on traversal attempts."""
        root = os.path.abspath(root)
        full = os.path.abspath(os.path.join(root, rel))
        if full == root or full.startswith(root + os.sep):
            return full
        return ""

    @staticmethod
    def _finish(resp: Response, start_response):
        resp.headers.append(("Content-Length", str(len(resp.body))))
        status = f"{resp.status} {_STATUS_TEXT.get(resp.status, 'Status')}"
        start_response(status, resp.headers)
        return [resp.body]


def _content_type(path: str) -> str:
    import mimetypes
    return mimetypes.guess_type(path)[0] or "application/octet-stream"
