"""Authorization-embedded K8s API access for the web apps.

Mirrors the reference's crud_backend/api/ package (notebook.py, pvc.py,
custom_resource.py, events.py, pod.py, poddefault.py, storageclass.py,
namespace.py): every call runs a SubjectAccessReview for the
authenticated user before touching the API server, so handlers cannot
forget the check.
"""

from __future__ import annotations

import dataclasses

from service_account_auth_improvements_tpu.webapps.core import authz

GROUP = "tpukf.dev"


@dataclasses.dataclass(frozen=True)
class _Kind:
    plural: str
    group: str
    version: str


KINDS = {
    "notebooks": _Kind("notebooks", GROUP, "v1beta1"),
    "poddefaults": _Kind("poddefaults", GROUP, "v1alpha1"),
    "tensorboards": _Kind("tensorboards", GROUP, "v1alpha1"),
    "pvcviewers": _Kind("pvcviewers", GROUP, "v1alpha1"),
    "profiles": _Kind("profiles", GROUP, "v1"),
    "persistentvolumeclaims": _Kind("persistentvolumeclaims", "", "v1"),
    "pods": _Kind("pods", "", "v1"),
    "events": _Kind("events", "", "v1"),
    "secrets": _Kind("secrets", "", "v1"),
    "namespaces": _Kind("namespaces", "", "v1"),
    "storageclasses": _Kind("storageclasses", "storage.k8s.io", "v1"),
}


class KubeApi:
    """Per-request façade: bound to the caller's identity so every verb is
    SubjectAccessReview-gated (reference crud_backend/api/notebook.py:14-21
    repeats this pattern per resource; here it is centralized)."""

    def __init__(self, kube, user: str | None, mode: str | None = None):
        self.kube = kube
        self.user = user
        self.mode = mode

    def _ensure(self, verb: str, kind: _Kind,
                namespace: str | None = None) -> None:
        authz.ensure_authorized(
            self.kube, self.user, verb, kind.group, kind.version,
            kind.plural, namespace=namespace, mode=self.mode,
        )

    def _kind(self, plural: str) -> _Kind:
        return KINDS[plural]

    # ----------------------------------------------------------- generic

    def list(self, plural: str, namespace: str | None = None,
             label_selector: str = "", field_selector: str = "") -> list:
        kind = self._kind(plural)
        self._ensure("list", kind, namespace)
        out = self.kube.list(
            kind.plural, namespace=namespace, label_selector=label_selector,
            field_selector=field_selector, group=kind.group or None,
        )
        return out.get("items", [])

    def get(self, plural: str, name: str,
            namespace: str | None = None) -> dict:
        kind = self._kind(plural)
        self._ensure("get", kind, namespace)
        return self.kube.get(kind.plural, name, namespace=namespace,
                             group=kind.group or None)

    def create(self, plural: str, obj: dict,
               namespace: str | None = None) -> dict:
        kind = self._kind(plural)
        self._ensure("create", kind, namespace)
        return self.kube.create(kind.plural, obj, namespace=namespace,
                                group=kind.group or None)

    def delete(self, plural: str, name: str,
               namespace: str | None = None) -> dict:
        kind = self._kind(plural)
        self._ensure("delete", kind, namespace)
        return self.kube.delete(kind.plural, name, namespace=namespace,
                                group=kind.group or None)

    def patch(self, plural: str, name: str, patch,
              namespace: str | None = None, patch_type: str = "merge") -> dict:
        kind = self._kind(plural)
        self._ensure("patch", kind, namespace)
        return self.kube.patch(kind.plural, name, patch, namespace=namespace,
                               group=kind.group or None,
                               patch_type=patch_type)

    def update(self, plural: str, obj: dict,
               namespace: str | None = None) -> dict:
        kind = self._kind(plural)
        self._ensure("update", kind, namespace)
        return self.kube.update(kind.plural, obj, namespace=namespace,
                                group=kind.group or None)

    # --------------------------------------------------------- shortcuts

    def events_for(self, namespace: str, kind: str, name: str) -> list:
        """Events for one object, newest last (reference api/events.py)."""
        items = self.list(
            "events", namespace=namespace,
            field_selector=f"involvedObject.kind={kind},"
                           f"involvedObject.name={name}",
        )
        return sorted(
            items,
            key=lambda e: e.get("lastTimestamp")
            or e.get("eventTime") or "",
        )

    def pod_logs(self, namespace: str, pod: str,
                 container: str | None = None,
                 tail_lines: int | None = None) -> str:
        """SAR-gated on the ``pods/log`` subresource (reference
        crud_backend/api/pod.py get_pod_logs:14-21)."""
        kind = self._kind("pods")
        authz.ensure_authorized(
            self.kube, self.user, "get", kind.group, kind.version,
            kind.plural, namespace=namespace, subresource="log",
            mode=self.mode,
        )
        return self.kube.pod_logs(pod, namespace=namespace,
                                  container=container,
                                  tail_lines=tail_lines)

    def pods_using_pvc(self, namespace: str, pvc: str) -> list:
        """Reference api/pod.py list_pods filtered by PVC volume."""
        out = []
        for pod in self.list("pods", namespace=namespace):
            for vol in (pod.get("spec") or {}).get("volumes") or []:
                claim = vol.get("persistentVolumeClaim") or {}
                if claim.get("claimName") == pvc:
                    out.append(pod)
                    break
        return out
