"""Shared CRUD-backend library for the web apps.

Stdlib-WSGI re-imagining of the reference's Flask crud_backend
(components/crud-web-apps/common/backend/kubeflow/kubeflow/crud_backend/
__init__.py:16 create_app): app factory wiring authn (trusted userid
header), authz (SubjectAccessReview per request), CSRF (double-submit
cookie), probes, error handlers, and SPA static serving.
"""

from service_account_auth_improvements_tpu.webapps.core.app import (
    HttpError,
    Request,
    WebApp,
    frontend_dirs,
)
from service_account_auth_improvements_tpu.webapps.core.status import (
    STATUS_PHASE,
    create_status,
)

__all__ = [
    "HttpError", "Request", "WebApp", "STATUS_PHASE", "create_status",
    "frontend_dirs",
]
