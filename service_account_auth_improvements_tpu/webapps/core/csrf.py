"""CSRF protection: double-submit cookie + custom header.

The index response sets a random ``XSRF-TOKEN`` cookie; the SPA echoes it
in an ``X-XSRF-TOKEN`` header on every unsafe request and the backend
requires the pair to match (reference: crud_backend/csrf.py:48-118).
"""

from __future__ import annotations

import os
import secrets

from service_account_auth_improvements_tpu.webapps.core import settings

CSRF_COOKIE = "XSRF-TOKEN"
CSRF_HEADER = "X-" + CSRF_COOKIE
SAFE_METHODS = ("GET", "HEAD", "OPTIONS", "TRACE")
SAMESITE_VALUES = ("Strict", "Lax", "None")


def set_cookie(resp, prefix: str = "/") -> None:
    token = secrets.token_urlsafe(32)
    samesite = os.environ.get("CSRF_SAMESITE", "Strict")
    if samesite not in SAMESITE_VALUES:
        samesite = "Strict"
    attrs = [
        f"{CSRF_COOKIE}={token}",
        f"Path={prefix}",
        f"SameSite={samesite}",
    ]
    if settings.secure_cookies():
        attrs.append("Secure")
    # HttpOnly deliberately absent: the SPA must read the cookie to echo
    # it back in the header.
    resp.headers.append(("Set-Cookie", "; ".join(attrs)))


def check(req) -> None:
    from service_account_auth_improvements_tpu.webapps.core.app import (
        HttpError,
    )

    if req.method in SAFE_METHODS:
        return
    cookie = req.cookies.get(CSRF_COOKIE)
    if not cookie:
        raise HttpError(
            403, f"Could not find CSRF cookie {CSRF_COOKIE} in the request."
        )
    header = req.header(CSRF_HEADER)
    if not header:
        raise HttpError(
            403, f"Could not detect CSRF protection header {CSRF_HEADER}."
        )
    if header != cookie:
        raise HttpError(
            403, "CSRF check failed. Token in cookie doesn't match token "
            "in header.",
        )
