"""Shared status vocabulary for the web frontends
(reference: crud_backend/status.py — the frontend expects exactly these
phase strings)."""

from __future__ import annotations


class STATUS_PHASE:
    READY = "ready"
    WAITING = "waiting"
    WARNING = "warning"
    ERROR = "error"
    UNINITIALIZED = "uninitialized"
    UNAVAILABLE = "unavailable"
    TERMINATING = "terminating"
    STOPPED = "stopped"
    #: checkpoint-parked (controlplane/parking): scale-to-zero with
    #: committed state — distinct from STOPPED so the frontend renders
    #: "resume on open" instead of a generic halt
    PARKED = "parked"


def create_status(phase: str = "", message: str = "",
                  state: str = "") -> dict:
    return {"phase": phase, "message": message, "state": state}
