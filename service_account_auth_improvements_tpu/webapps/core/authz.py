"""Authorization via Kubernetes SubjectAccessReview.

Every API handler asks the K8s RBAC layer whether the authenticated user
may perform the verb on the resource (reference: crud_backend/
authz.py:25-113 — create_subject_access_review / is_authorized /
ensure_authorized). RBAC stays the single source of truth; the web tier
holds no policy of its own.
"""

from __future__ import annotations

import logging

from service_account_auth_improvements_tpu.webapps.core import settings
from service_account_auth_improvements_tpu.webapps.core.app import HttpError

log = logging.getLogger(__name__)

AUTHZ_GROUP = "authorization.k8s.io"


def is_authorized(kube, user: str | None, verb: str, group: str,
                  version: str, resource: str, namespace: str | None = None,
                  subresource: str | None = None,
                  mode: str | None = None) -> bool:
    if settings.dev_mode(mode) or settings.disable_auth():
        return True
    if user is None:
        raise HttpError(401, "No user credentials were found!")
    sar = {
        "apiVersion": f"{AUTHZ_GROUP}/v1",
        "kind": "SubjectAccessReview",
        "spec": {
            "user": user,
            "resourceAttributes": {
                "group": group,
                "namespace": namespace,
                "verb": verb,
                "resource": resource,
                "version": version,
                "subresource": subresource,
            },
        },
    }
    out = kube.create("subjectaccessreviews", sar, group=AUTHZ_GROUP)
    status = out.get("status")
    if status is None:
        log.error("SubjectAccessReview doesn't have status.")
        return False
    return bool(status.get("allowed"))


def unauthorized_message(user, verb, group, version, resource,
                         subresource=None, namespace=None) -> str:
    msg = f"User '{user}' is not authorized to {verb}"
    msg += f" {version}/{resource}" if not group else \
        f" {group}/{version}/{resource}"
    if subresource:
        msg += f"/{subresource}"
    if namespace:
        msg += f" in namespace '{namespace}'"
    return msg


def ensure_authorized(kube, user, verb, group, version, resource,
                      namespace=None, subresource=None,
                      mode: str | None = None) -> None:
    if not is_authorized(kube, user, verb, group, version, resource,
                         namespace=namespace, subresource=subresource,
                         mode=mode):
        raise HttpError(403, unauthorized_message(
            user, verb, group, version, resource,
            subresource=subresource, namespace=namespace,
        ))
