"""Authentication from the trusted userid header.

The Istio ingress/auth layer injects the user's identity as an HTTP
header; the backends trust it (reference: crud_backend/authn.py:12-23
get_username, settings.py env knobs). ``no_authentication`` marks a route
as public (authn.py:26-32).
"""

from __future__ import annotations

from service_account_auth_improvements_tpu.webapps.core import settings


def get_username(environ: dict) -> str | None:
    key = "HTTP_" + settings.userid_header().upper().replace("-", "_")
    if key not in environ:
        return None
    user = environ[key]
    return user.replace(settings.userid_prefix(), "")


def no_authentication(fn):
    fn.no_authentication = True
    return fn
