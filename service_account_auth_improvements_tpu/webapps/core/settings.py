"""Env-var settings (reference: crud_backend/settings.py:1-6 and
config.py BackendMode — dev mode skips authn/authz so Cypress-style e2e
can run without Istio, crud_backend/config.py:18-21, authn.py:41-43)."""

from __future__ import annotations

import os


def userid_header() -> str:
    return os.environ.get("USERID_HEADER", "kubeflow-userid")


def userid_prefix() -> str:
    return os.environ.get("USERID_PREFIX", ":")


def disable_auth() -> bool:
    return os.environ.get("APP_DISABLE_AUTH", "false").lower() == "true"


def secure_cookies() -> bool:
    return os.environ.get("APP_SECURE_COOKIES", "true").lower() == "true"


def dev_mode(mode: str | None = None) -> bool:
    mode = mode if mode is not None else os.environ.get("BACKEND_MODE", "prod")
    return mode in ("dev", "development")
