"""Backend-for-frontend web apps (SURVEY.md §1 L4/L5).

``core`` is the shared library (the reference's crud_backend —
components/crud-web-apps/common/backend/kubeflow/kubeflow/crud_backend/);
``jupyter``/``volumes``/``tensorboards`` are the per-resource apps and
``dashboard`` is the central-dashboard BFF.
"""
