"""Notebook status aggregation for the UI.

Priority chain ported from the reference (jupyter backend
apps/common/status.py:9-57 process_status): empty → stopped →
terminating → ready → containerState → conditions → warning events →
generic warning. Multi-host twist: "ready" means every host of the slice
is ready, not replicas==1 (the reference is single-pod).
"""

from __future__ import annotations

import datetime as dt
import re

from service_account_auth_improvements_tpu.controlplane import tpu
from service_account_auth_improvements_tpu.controlplane.controllers.notebook import (
    STOP_ANNOTATION,
)
from service_account_auth_improvements_tpu.webapps.core import (
    STATUS_PHASE,
    create_status,
)

EVENT_TYPE_WARNING = "Warning"


def expected_hosts(notebook: dict) -> int:
    try:
        resolved = tpu.resolve((notebook.get("spec") or {}).get("tpu"))
    except tpu.TpuValidationError:
        return 1
    return resolved.num_hosts if resolved else 1


_QUEUE_POSITION = re.compile(r"queue position (\d+)/(\d+)")


def queue_info(notebook: dict) -> dict | None:
    """Parsed tpusched parking state (``Scheduled=False``), or None when
    the notebook is placed / stopped / not scheduler-managed. Shape:
    ``{reason, message, position, of}`` — position/of None when the
    condition carries no queue position yet. Prefers the condition's
    structured ``queuePosition``/``queueTotal`` fields; the regex over
    the prose message is a fallback for conditions written before those
    fields existed."""
    meta = notebook.get("metadata") or {}
    if STOP_ANNOTATION in (meta.get("annotations") or {}):
        # a stopped notebook left the queue; its last Scheduled=False
        # condition is history, not a live queue entry
        return None
    for cond in (notebook.get("status") or {}).get("conditions") or []:
        if cond.get("type") != "Scheduled":
            continue
        if cond.get("status") != "False":
            return None
        message = cond.get("message") or ""
        position, of = cond.get("queuePosition"), cond.get("queueTotal")
        if position is None:
            m = _QUEUE_POSITION.search(message)
            position = int(m.group(1)) if m else None
            of = int(m.group(2)) if m else None
        return {
            "reason": cond.get("reason") or "Unschedulable",
            "message": message,
            "position": position,
            "of": of,
        }
    return None


def process_status(notebook: dict, events: list | None = None) -> dict:
    meta = notebook.get("metadata") or {}
    nb_status = notebook.get("status") or {}
    ready = nb_status.get("readyReplicas", 0)
    annotations = meta.get("annotations") or {}

    # Fresh CR with no status yet: generic waiting for the first moments.
    if not nb_status.get("containerState") and not nb_status.get("conditions"):
        created = meta.get("creationTimestamp")
        if created:
            age = (
                dt.datetime.now(dt.timezone.utc).replace(tzinfo=None)
                - dt.datetime.strptime(created, "%Y-%m-%dT%H:%M:%SZ")
            ).total_seconds()
            if age <= 10:
                return create_status(
                    STATUS_PHASE.WAITING,
                    "Waiting for StatefulSet to create the underlying Pod.",
                )

    if STOP_ANNOTATION in annotations:
        if ready == 0:
            if nb_status.get("phase") == "Parked":
                # checkpoint-parked (controlplane/parking), not merely
                # stopped: state is committed and a start re-admits +
                # restores — say so instead of the generic halt
                return create_status(
                    STATUS_PHASE.PARKED,
                    "Parked (resume on open) — notebook state is "
                    "checkpointed; starting restores it.",
                )
            return create_status(
                STATUS_PHASE.STOPPED,
                "No Pods are currently running for this Notebook Server.",
            )
        return create_status(
            STATUS_PHASE.WAITING, "Notebook Server is stopping."
        )

    if "deletionTimestamp" in meta:
        return create_status(
            STATUS_PHASE.TERMINATING, "Deleting this Notebook Server."
        )

    hosts = expected_hosts(notebook)
    if ready == 0:
        # Parked by tpusched: not an error — the user sees WHY (reason +
        # queue position) instead of a bare Pending that never explains
        # itself. Checked only while nothing is running: a stale
        # condition (scheduler later disabled) must never mask a live
        # server.
        queued = queue_info(notebook)
        if queued:
            return create_status(
                STATUS_PHASE.WAITING,
                f"{queued['reason']}: {queued['message']}",
            )

    if ready >= hosts:
        msg = "Running" if hosts == 1 else \
            f"Running on all {hosts} hosts of the slice"
        return create_status(STATUS_PHASE.READY, msg)
    if ready > 0:
        return create_status(
            STATUS_PHASE.WAITING,
            f"{ready}/{hosts} slice hosts are ready.",
        )

    state = nb_status.get("containerState") or {}
    if "waiting" in state:
        waiting = state["waiting"]
        reason = waiting.get("reason", "Undefined")
        if reason == "PodInitializing":
            return create_status(STATUS_PHASE.WAITING, reason)
        return create_status(
            STATUS_PHASE.WARNING,
            f"{reason}: "
            f"{waiting.get('message', 'No available message.')}",
        )

    for condition in nb_status.get("conditions") or []:
        if "reason" in condition:
            return create_status(
                STATUS_PHASE.WARNING,
                f"{condition['reason']}: {condition.get('message', '')}",
            )

    for event in sorted(
        events or [],
        key=lambda e: e.get("lastTimestamp") or "", reverse=True,
    ):
        if event.get("type") == EVENT_TYPE_WARNING:
            return create_status(
                STATUS_PHASE.WARNING, event.get("message", "")
            )

    return create_status(
        STATUS_PHASE.WARNING,
        "Couldn't find any information for the status of this notebook.",
    )
