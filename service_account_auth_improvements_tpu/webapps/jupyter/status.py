"""Notebook status aggregation for the UI.

Priority chain ported from the reference (jupyter backend
apps/common/status.py:9-57 process_status): empty → stopped →
terminating → ready → containerState → conditions → warning events →
generic warning. Multi-host twist: "ready" means every host of the slice
is ready, not replicas==1 (the reference is single-pod).
"""

from __future__ import annotations

import datetime as dt

from service_account_auth_improvements_tpu.controlplane import tpu
from service_account_auth_improvements_tpu.controlplane.controllers.notebook import (
    STOP_ANNOTATION,
)
from service_account_auth_improvements_tpu.webapps.core import (
    STATUS_PHASE,
    create_status,
)

EVENT_TYPE_WARNING = "Warning"


def expected_hosts(notebook: dict) -> int:
    try:
        resolved = tpu.resolve((notebook.get("spec") or {}).get("tpu"))
    except tpu.TpuValidationError:
        return 1
    return resolved.num_hosts if resolved else 1


def process_status(notebook: dict, events: list | None = None) -> dict:
    meta = notebook.get("metadata") or {}
    nb_status = notebook.get("status") or {}
    ready = nb_status.get("readyReplicas", 0)
    annotations = meta.get("annotations") or {}

    # Fresh CR with no status yet: generic waiting for the first moments.
    if not nb_status.get("containerState") and not nb_status.get("conditions"):
        created = meta.get("creationTimestamp")
        if created:
            age = (
                dt.datetime.now(dt.timezone.utc).replace(tzinfo=None)
                - dt.datetime.strptime(created, "%Y-%m-%dT%H:%M:%SZ")
            ).total_seconds()
            if age <= 10:
                return create_status(
                    STATUS_PHASE.WAITING,
                    "Waiting for StatefulSet to create the underlying Pod.",
                )

    if STOP_ANNOTATION in annotations:
        if ready == 0:
            return create_status(
                STATUS_PHASE.STOPPED,
                "No Pods are currently running for this Notebook Server.",
            )
        return create_status(
            STATUS_PHASE.WAITING, "Notebook Server is stopping."
        )

    if "deletionTimestamp" in meta:
        return create_status(
            STATUS_PHASE.TERMINATING, "Deleting this Notebook Server."
        )

    hosts = expected_hosts(notebook)
    if ready >= hosts:
        msg = "Running" if hosts == 1 else \
            f"Running on all {hosts} hosts of the slice"
        return create_status(STATUS_PHASE.READY, msg)
    if ready > 0:
        return create_status(
            STATUS_PHASE.WAITING,
            f"{ready}/{hosts} slice hosts are ready.",
        )

    state = nb_status.get("containerState") or {}
    if "waiting" in state:
        waiting = state["waiting"]
        reason = waiting.get("reason", "Undefined")
        if reason == "PodInitializing":
            return create_status(STATUS_PHASE.WAITING, reason)
        return create_status(
            STATUS_PHASE.WARNING,
            f"{reason}: "
            f"{waiting.get('message', 'No available message.')}",
        )

    for condition in nb_status.get("conditions") or []:
        if "reason" in condition:
            return create_status(
                STATUS_PHASE.WARNING,
                f"{condition['reason']}: {condition.get('message', '')}",
            )

    for event in sorted(
        events or [],
        key=lambda e: e.get("lastTimestamp") or "", reverse=True,
    ):
        if event.get("type") == EVENT_TYPE_WARNING:
            return create_status(
                STATUS_PHASE.WARNING, event.get("message", "")
            )

    return create_status(
        STATUS_PHASE.WARNING,
        "Couldn't find any information for the status of this notebook.",
    )
