"""Spawner UI configuration.

The reference mounts ``spawner_ui_config.yaml`` as a ConfigMap and
re-reads it on every request so edits hot-reload (reference: jupyter
backend apps/common/utils.py load_spawner_ui_config; GPU vendor section
at yaml/spawner_ui_config.yaml:119-141). Here the accelerator section is
a TPU picker: generation + topology dropdowns that the form compiles to
``spec.tpu`` — the control plane resolves chips/hosts/selectors from it
(controlplane/tpu.py).
"""

from __future__ import annotations

import copy
import os

import yaml

from service_account_auth_improvements_tpu.controlplane import tpu

CONFIG_ENV = "JWA_UI_CONFIG"

DEFAULT_CONFIG: dict = {
    "image": {
        "value": "ghcr.io/tpukf/jupyter-jax-tpu:latest",
        "options": [
            "ghcr.io/tpukf/jupyter-jax-tpu:latest",
            "ghcr.io/tpukf/jupyter-scipy:latest",
            "ghcr.io/tpukf/codeserver-python:latest",
        ],
        "readOnly": False,
    },
    "imagePullPolicy": {"value": "IfNotPresent", "readOnly": False},
    "serverType": {"value": "jupyter", "readOnly": False},
    "cpu": {"value": "0.5", "limitFactor": "1.2", "readOnly": False},
    "memory": {"value": "1.0Gi", "limitFactor": "1.2", "readOnly": False},
    # The TPU picker (replaces the reference's `gpus.vendors` dropdown).
    "tpu": {
        "readOnly": False,
        "value": {"generation": "none", "topology": ""},
        "generations": [
            {
                "key": gen,
                "uiName": f"TPU {gen}",
                "topologies": topos,
            }
            for gen, topos in (
                ("v4", ["2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4"]),
                ("v5e", ["1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16",
                         "16x16"]),
                ("v5p", ["2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4",
                         "4x4x8"]),
                ("v6e", ["1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16",
                         "16x16"]),
            )
        ],
    },
    "workspaceVolume": {
        "value": {
            "mount": "/home/jovyan",
            "newPvc": {
                "metadata": {"name": "{notebook-name}-workspace"},
                "spec": {
                    "resources": {"requests": {"storage": "10Gi"}},
                    "accessModes": ["ReadWriteOnce"],
                },
            },
        },
        "readOnly": False,
    },
    "dataVolumes": {"value": [], "readOnly": False},
    "tolerationGroup": {"value": "none", "options": [], "readOnly": False},
    "affinityConfig": {"value": "none", "options": [], "readOnly": False},
    "configurations": {"value": [], "readOnly": False},
    "shm": {"value": True, "readOnly": False},
    "environment": {"value": {}, "readOnly": False},
}


def load_spawner_ui_config() -> dict:
    """Per-request load so a mounted ConfigMap hot-reloads; the file only
    needs to override the sections it cares about."""
    path = os.environ.get(CONFIG_ENV, "")
    config = copy.deepcopy(DEFAULT_CONFIG)
    if path and os.path.exists(path):
        with open(path) as f:
            loaded = yaml.safe_load(f) or {}
        config.update(loaded.get("spawnerFormDefaults", loaded))
    return config


def validate_tpu_choice(config: dict, generation: str, topology: str) -> None:
    """The picker only offers supported combinations; reject anything else
    before it reaches the CR (the controller re-validates, tpu.py)."""
    gens = {g["key"]: g for g in config["tpu"].get("generations", [])}
    if generation not in gens:
        raise tpu.TpuValidationError(
            f"unknown TPU generation {generation!r}; "
            f"choose one of {sorted(gens)}"
        )
    topos = gens[generation].get("topologies", [])
    if topos and topology not in topos:
        raise tpu.TpuValidationError(
            f"topology {topology!r} not offered for {generation}; "
            f"choose one of {topos}"
        )
