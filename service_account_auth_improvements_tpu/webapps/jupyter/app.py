"""Jupyter web app routes.

The reference's JWA API surface (jupyter backend apps/default/routes/
post.py:12-75, apps/common/routes/{get,patch,delete}.py): spawner config,
PVC/PodDefault/Notebook listings, Notebook creation from the form,
start/stop via the stop annotation, deletion. All authz flows through
SubjectAccessReview (webapps/core/api.py).
"""

from __future__ import annotations

import datetime as dt

from service_account_auth_improvements_tpu.controlplane.controllers.notebook import (
    STOP_ANNOTATION,
)
from service_account_auth_improvements_tpu.controlplane import parking
from service_account_auth_improvements_tpu.webapps.core import (
    frontend_dirs,
    HttpError,
    WebApp,
)
from service_account_auth_improvements_tpu.webapps.core.api import KubeApi
from service_account_auth_improvements_tpu.webapps.jupyter import (
    config,
    form,
    status,
)


DEFAULT_LOG_TAIL_LINES = 1000


def _now() -> str:
    return dt.datetime.now(dt.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def app_container_name(pod: dict, notebook: str | None = None) -> str | None:
    """The notebook container to read logs from.

    Sidecar injection (Istio with holdApplicationUntilProxyStarts) can
    reorder containers, so containers[0] is a last resort: prefer the
    container named after the notebook (the spawner's convention and the
    reference's — its JWA uses the notebook name as the container name),
    then the 'notebook' default the controller stamps on bare CRs."""
    containers = (pod.get("spec") or {}).get("containers") or []
    for want in (notebook, "notebook"):
        if want:
            for c in containers:
                if c.get("name") == want:
                    return want
    return containers[0].get("name") if containers else None


def notebook_summary(nb: dict, events: list | None = None) -> dict:
    """Row shape the frontend table renders (reference apps/common/
    utils.py notebook_dict_from_k8s_obj), plus the TPU block."""
    meta = nb["metadata"]
    containers = (
        ((nb.get("spec") or {}).get("template") or {}).get("spec") or {}
    ).get("containers") or []
    # Tolerate kubectl-created CRs with minimal specs: one malformed
    # object must not 500 the whole listing.
    container = containers[0] if containers else {}
    tpu_spec = (nb.get("spec") or {}).get("tpu") or None
    return {
        "name": meta["name"],
        "namespace": meta.get("namespace"),
        "serverType": (meta.get("annotations") or {}).get(
            form.SERVER_TYPE_ANNOTATION
        ),
        "age": meta.get("creationTimestamp"),
        "image": container.get("image"),
        "shortImage": (container.get("image") or "").split("/")[-1],
        "cpu": (container.get("resources") or {}).get(
            "requests", {}
        ).get("cpu"),
        "memory": (container.get("resources") or {}).get(
            "requests", {}
        ).get("memory"),
        "tpu": tpu_spec,
        "labels": meta.get("labels"),
        "annotations": meta.get("annotations"),
        "status": status.process_status(nb, events),
        # tpusched parking state ({reason, message, position, of} or
        # None) — the frontend renders "queued N/M" on the status row
        "queue": status.queue_info(nb),
    }


def build_app(kube, static_dir: str | None = None,
              mode: str | None = None) -> WebApp:
    default_static, shared = frontend_dirs("jupyter")
    app = WebApp("jupyter-web-app", static_dir=static_dir or default_static,
                 mode=mode, shared_static_dir=shared)

    def api_for(req) -> KubeApi:
        return KubeApi(kube, req.user, mode=app.mode)

    # ------------------------------------------------------------- reads

    @app.route("GET", "/api/config")
    def get_config(req):
        return {"config": config.load_spawner_ui_config()}

    @app.route("GET", "/api/namespaces/<namespace>/pvcs")
    def get_pvcs(req):
        ns = req.params["namespace"]
        pvcs = api_for(req).list("persistentvolumeclaims", ns)
        return {"pvcs": [{
            "name": p["metadata"]["name"],
            "size": (p["spec"].get("resources") or {}).get(
                "requests", {}
            ).get("storage"),
            "mode": (p["spec"].get("accessModes") or [""])[0],
        } for p in pvcs]}

    @app.route("GET", "/api/namespaces/<namespace>/poddefaults")
    def get_poddefaults(req):
        ns = req.params["namespace"]
        contents = []
        for pd in api_for(req).list("poddefaults", ns):
            spec = pd.get("spec") or {}
            match_labels = (spec.get("selector") or {}).get(
                "matchLabels"
            ) or {}
            pd["label"] = next(iter(match_labels), "")
            pd["desc"] = spec.get("desc", pd["metadata"]["name"])
            contents.append(pd)
        return {"poddefaults": contents}

    @app.route("GET", "/api/namespaces/<namespace>/notebooks")
    def get_notebooks(req):
        ns = req.params["namespace"]
        nbs = api_for(req).list("notebooks", ns)
        return {"notebooks": [notebook_summary(nb) for nb in nbs]}

    @app.route("GET", "/api/namespaces/<namespace>/notebooks/<name>")
    def get_notebook(req):
        ns, name = req.params["namespace"], req.params["name"]
        api = api_for(req)
        nb = api.get("notebooks", name, ns)
        events = api.events_for(ns, "Notebook", name)
        return {"notebook": nb, "summary": notebook_summary(nb, events),
                "events": events}

    # --------------------------------------------- notebook details views
    # (reference: jupyter/backend/apps/common/routes/get.py:68-100 — on a
    # TPU platform "why is my slice pod Pending/CrashLooping" is THE
    # debugging question, so the pod/logs/events surface is first-class)

    @app.route("GET", "/api/namespaces/<namespace>/notebooks/<name>/pod")
    def get_notebook_pods(req):
        ns, name = req.params["namespace"], req.params["name"]
        pods = api_for(req).list(
            "pods", ns, label_selector=f"notebook-name={name}"
        )
        if not pods:
            raise HttpError(404, "No pod detected.")
        pods.sort(key=lambda p: p["metadata"]["name"])
        # multi-host slices have one pod per host; "pod" stays the rank-0
        # pod for reference-shape compatibility
        return {"pod": pods[0], "pods": pods}

    @app.route(
        "GET",
        "/api/namespaces/<namespace>/notebooks/<name>/pod/<pod>/logs",
    )
    def get_pod_logs(req):
        ns = req.params["namespace"]
        name, pod_name = req.params["name"], req.params["pod"]
        api = api_for(req)
        pod = api.get("pods", pod_name, ns)
        if (pod["metadata"].get("labels") or {}).get(
                "notebook-name") != name:
            raise HttpError(
                404, f"Pod {pod_name} does not belong to notebook {name}."
            )
        # cap the transfer: the UI polls this every few seconds, and a
        # long-running pod's full log is arbitrarily large
        try:
            tail = int(req.query.get("tailLines", DEFAULT_LOG_TAIL_LINES))
        except ValueError:
            raise HttpError(400, "tailLines must be an integer")
        logs = api.pod_logs(ns, pod_name,
                            container=app_container_name(pod, name),
                            tail_lines=tail)
        return {"logs": logs.split("\n")}

    @app.route("GET", "/api/namespaces/<namespace>/notebooks/<name>/events")
    def get_notebook_events(req):
        ns, name = req.params["namespace"], req.params["name"]
        return {"events":
                api_for(req).events_for(ns, "Notebook", name)}

    # ------------------------------------------------------------ writes

    @app.route("POST", "/api/namespaces/<namespace>/notebooks")
    def post_notebook(req):
        ns = req.params["namespace"]
        body = req.json()
        if "name" not in body:
            raise HttpError(400, "Request body must include 'name'")
        api = api_for(req)
        defaults = config.load_spawner_ui_config()
        nb = form.notebook_template(
            body["name"], ns, req.user or "anonymous@kubeflow.org"
        )
        form.set_image(nb, body, defaults)
        form.set_server_type(nb, body, defaults)
        form.set_cpu(nb, body, defaults)
        form.set_memory(nb, body, defaults)
        form.set_tpu(nb, body, defaults)
        form.set_tolerations(nb, body, defaults)
        form.set_affinity(nb, body, defaults)
        form.set_configurations(nb, body, defaults)
        form.set_shm(nb, body, defaults)
        form.set_environment(nb, body, defaults)

        volumes = form.volume_requests(body["name"], body, defaults)
        for vol in volumes:
            pvc = form.new_pvc_from(vol)
            if pvc is not None:
                created = api.create("persistentvolumeclaims", pvc, ns)
                pvc_name = created["metadata"]["name"]
            else:
                pvc_name = vol.get("existingSource") or vol.get("name")
                if not pvc_name:
                    raise HttpError(
                        400, "volume needs newPvc or existingSource/name"
                    )
            form.attach_volume(nb, vol, pvc_name)

        api.create("notebooks", nb, ns)
        return {"message": "Notebook created successfully."}

    @app.route("PATCH", "/api/namespaces/<namespace>/notebooks/<name>")
    def patch_notebook(req):
        ns, name = req.params["namespace"], req.params["name"]
        body = req.json()
        if "stopped" not in body:
            raise HttpError(
                400, "Request body must include at least one supported key: "
                "['stopped']"
            )
        api = api_for(req)
        if body["stopped"]:
            nb = api.get("notebooks", name, ns)
            if STOP_ANNOTATION in (nb["metadata"].get("annotations") or {}):
                raise HttpError(
                    409, f"Notebook {ns}/{name} is already stopped."
                )
            patch = {"metadata": {"annotations": {STOP_ANNOTATION: _now()}}}
        else:
            annotations = {STOP_ANNOTATION: None}
            nb = api.get("notebooks", name, ns)
            annots = nb["metadata"].get("annotations") or {}
            if parking.CHECKPOINT_ANNOTATION in annots:
                # starting a PARKED notebook is a resume: stamp the
                # request (the resume-latency SLO's start mark; the
                # culler restores from the checkpoint ref and clears the
                # park state) alongside the stop-clear that re-enters
                # tpusched admission
                annotations[parking.RESUME_REQUESTED_ANNOTATION] = _now()
            if parking.PARK_REQUESTED_ANNOTATION in annots:
                # a start racing an in-flight park request: the user
                # wins — clearing the request cancels the park
                annotations[parking.PARK_REQUESTED_ANNOTATION] = None
            patch = {"metadata": {"annotations": annotations}}
        api.patch("notebooks", name, patch, ns)
        return {"message": "ok"}

    @app.route("PUT", "/api/namespaces/<namespace>/notebooks/<name>")
    def put_notebook(req):
        """Whole-object update from the YAML editor (SAR-gated 'update');
        the reference's Monaco editor submits the same shape. The CR's
        identity and status are server-owned: name/namespace must match
        the URL and any submitted status is dropped."""
        ns, name = req.params["namespace"], req.params["name"]
        body = req.json()
        if not isinstance(body, dict) or "metadata" not in body:
            raise HttpError(400, "Request body must be a Notebook object")
        meta = body.get("metadata") or {}
        if meta.get("name", name) != name or \
                meta.get("namespace", ns) != ns:
            raise HttpError(
                400, "metadata.name/namespace must match the URL"
            )
        api = api_for(req)
        live = api.get("notebooks", name, ns)
        updated = dict(body)
        updated.pop("status", None)
        updated["apiVersion"] = live.get("apiVersion")
        updated["kind"] = live.get("kind")
        meta = dict(updated.get("metadata") or {})
        meta["name"] = name
        meta["namespace"] = ns
        # concurrency: honor the client's resourceVersion when provided
        # (stale edits 409), else overwrite on the live version
        meta.setdefault(
            "resourceVersion", live["metadata"].get("resourceVersion")
        )
        meta.setdefault("uid", live["metadata"].get("uid"))
        updated["metadata"] = meta
        api.update("notebooks", updated, ns)
        return {"message": f"Notebook {name} updated."}

    @app.route("DELETE", "/api/namespaces/<namespace>/notebooks/<name>")
    def delete_notebook(req):
        ns, name = req.params["namespace"], req.params["name"]
        api_for(req).delete("notebooks", name, ns)
        return {"message": f"Notebook {name} successfully deleted."}

    return app
