"""Jupyter web app (spawner UI backend) — the reference's JWA
(components/crud-web-apps/jupyter/backend/). TPU-native: the accelerator
picker is generation+topology (compiled to ``spec.tpu`` on the Notebook
CR) instead of a GPU vendor limits key."""

from service_account_auth_improvements_tpu.webapps.jupyter.app import (
    build_app,
)

__all__ = ["build_app"]
