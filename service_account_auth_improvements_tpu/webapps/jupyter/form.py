"""Form → Notebook CR compilation.

The reference builds the CR from a YAML template plus per-field setters
that honor the config's readOnly flags (jupyter backend
apps/common/form.py:74-283). The accelerator setter writes ``spec.tpu``
(resolved by the controller into chips/selectors/rendezvous env) instead
of a ``nvidia.com/gpu`` limits key (reference form.py:226-252).
"""

from __future__ import annotations

from service_account_auth_improvements_tpu.controlplane import tpu
from service_account_auth_improvements_tpu.webapps.core.app import HttpError
from service_account_auth_improvements_tpu.webapps.jupyter import config as \
    jwa_config

GROUP = "tpukf.dev"
SERVER_TYPE_ANNOTATION = "notebooks.tpukf.dev/server-type"
CREATOR_ANNOTATION = "notebooks.tpukf.dev/creator"
VALID_SERVER_TYPES = ("jupyter", "group-one", "group-two")


def notebook_template(name: str, namespace: str, creator: str) -> dict:
    """The reference's notebook_template.yaml as a literal (jupyter backend
    apps/common/yaml/notebook_template.yaml)."""
    return {
        "apiVersion": f"{GROUP}/v1beta1",
        "kind": "Notebook",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": {"app": name},
            "annotations": {
                SERVER_TYPE_ANNOTATION: "",
                CREATOR_ANNOTATION: creator,
            },
        },
        "spec": {
            "template": {"spec": {
                "serviceAccountName": "default-editor",
                "containers": [{
                    "name": name,
                    "image": "",
                    "volumeMounts": [],
                    "env": [],
                    "resources": {
                        "requests": {"cpu": "0.1", "memory": "0.1Gi"},
                    },
                }],
                "volumes": [],
                "tolerations": [],
            }},
        },
    }


def get_form_value(body: dict, defaults: dict, body_field: str,
                   defaults_field: str | None = None, optional: bool = False):
    """readOnly semantics (reference form.py:16-60): a readOnly field must
    not appear in the request; a writable field falls back to its default
    only when optional."""
    defaults_field = defaults_field or body_field
    user_value = body.get(body_field)
    if defaults_field not in defaults:
        return user_value
    entry = defaults[defaults_field]
    if entry.get("readOnly"):
        if body_field in body:
            raise HttpError(
                400, f"{body_field!r} is readonly but a value was provided"
            )
        return entry.get("value")
    if user_value is None:
        if body_field in body:
            return None  # explicit null
        if optional:
            return entry.get("value")
        raise HttpError(400, f"No value provided for: {body_field}")
    return user_value


def _container(nb: dict) -> dict:
    return nb["spec"]["template"]["spec"]["containers"][0]


def _pod_spec(nb: dict) -> dict:
    return nb["spec"]["template"]["spec"]


def set_image(nb: dict, body: dict, defaults: dict) -> None:
    field = "customImage" if body.get("customImage") else "image"
    image = get_form_value(body, defaults, field, "image", optional=True)
    if not image:
        raise HttpError(400, "No value provided for: image")
    _container(nb)["image"] = str(image).strip()
    policy = get_form_value(body, defaults, "imagePullPolicy", optional=True)
    if policy:
        _container(nb)["imagePullPolicy"] = policy


def set_server_type(nb: dict, body: dict, defaults: dict) -> None:
    server_type = get_form_value(body, defaults, "serverType",
                                 optional=True) or "jupyter"
    if server_type not in VALID_SERVER_TYPES:
        raise HttpError(400, f"{server_type!r} is not a valid server type")
    annotations = nb["metadata"]["annotations"]
    annotations[SERVER_TYPE_ANNOTATION] = server_type
    if server_type in ("group-one", "group-two"):
        annotations["notebooks.tpukf.dev/http-rewrite-uri"] = "/"
    if server_type == "group-two":
        ns, name = nb["metadata"]["namespace"], nb["metadata"]["name"]
        annotations["notebooks.tpukf.dev/http-headers-request-set"] = (
            '{"X-RStudio-Root-Path":"/notebook/%s/%s/"}' % (ns, name)
        )


_CPU_SUFFIX = {"m": 1e-3, "": 1.0}
_MEM_SUFFIX = {  # bytes per unit
    "": 1, "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40,
}


def parse_quantity(value: str, field: str) -> tuple[float, str]:
    """K8s quantity → (normalized number, suffix). cpu normalizes to
    cores, memory to the raw multiplier of its own suffix."""
    value = str(value).strip()
    suffixes = _CPU_SUFFIX if field == "cpu" else _MEM_SUFFIX
    for suffix in sorted(suffixes, key=len, reverse=True):
        if suffix and value.endswith(suffix):
            number = value[: -len(suffix)]
            break
    else:
        suffix, number = "", value
    try:
        num = float(number)
    except ValueError:
        raise HttpError(400, f"Invalid value for {field}: {value!r}")
    return num * suffixes[suffix], suffix


def _set_resource(nb: dict, body: dict, defaults: dict, field: str) -> None:
    """cpu/memory request + limitFactor-derived limit (reference
    form.py:118-176). Accepts any K8s quantity suffix ("500m", "512Mi")
    — the reference only handled bare cores / Gi."""
    value = get_form_value(body, defaults, field, optional=True)
    if value is None:
        return
    value = str(value)
    request_norm, suffix = parse_quantity(value, field)
    limit = body.get(field + "Limit")
    factor = defaults.get(field, {}).get("limitFactor", "none")
    if limit is None and factor != "none":
        # Keep the limit in the same unit the user chose.
        raw = float(value.removesuffix(suffix)) * float(factor)
        limit = f"{round(raw, 1):g}{suffix}"
    container = _container(nb)
    key = "cpu" if field == "cpu" else "memory"
    container["resources"].setdefault("requests", {})[key] = value
    if limit:
        limit = str(limit)
        limit_norm, _ = parse_quantity(limit, field)
        if limit_norm < request_norm:
            raise HttpError(
                400, f"{field} limit must be greater than the request"
            )
        container["resources"].setdefault("limits", {})[key] = limit


def set_cpu(nb, body, defaults):
    _set_resource(nb, body, defaults, "cpu")


def set_memory(nb, body, defaults):
    _set_resource(nb, body, defaults, "memory")


def set_tpu(nb: dict, body: dict, defaults: dict) -> None:
    """The accelerator setter. Form value {generation, topology} (or
    {generation, chips}); "none" means CPU-only. Validated against the
    picker config, then stored as spec.tpu for the controller to resolve
    (controlplane/tpu.py resolve)."""
    choice = get_form_value(body, defaults, "tpu", optional=True)
    if not choice:
        return
    generation = str(choice.get("generation", "none")).lower()
    if generation in ("", "none"):
        return
    topology = str(choice.get("topology", "")).lower()
    chips = choice.get("chips")
    spec: dict = {"generation": generation}
    if topology:
        spec["topology"] = topology
    if chips is not None:
        spec["chips"] = int(chips)
    # Fail fast with the picker's offerings and the same validator the
    # controller uses.
    try:
        if topology:
            jwa_config.validate_tpu_choice(defaults, generation, topology)
        tpu.resolve(spec)
    except tpu.TpuValidationError as e:
        raise HttpError(400, str(e))
    nb["spec"]["tpu"] = spec


def set_tolerations(nb: dict, body: dict, defaults: dict) -> None:
    key = get_form_value(body, defaults, "tolerationGroup", optional=True)
    if not key or key == "none":
        return
    for group in defaults.get("tolerationGroup", {}).get("options", []):
        if group.get("groupKey") == key:
            _pod_spec(nb)["tolerations"].extend(group.get("tolerations", []))
            return


def set_affinity(nb: dict, body: dict, defaults: dict) -> None:
    key = get_form_value(body, defaults, "affinityConfig", optional=True)
    if not key or key == "none":
        return
    for cfg in defaults.get("affinityConfig", {}).get("options", []):
        if cfg.get("configKey") == key:
            _pod_spec(nb)["affinity"] = cfg.get("affinity", {})
            return


def set_configurations(nb: dict, body: dict, defaults: dict) -> None:
    """PodDefault labels: the admission webhook matches them
    (reference form.py:255-263)."""
    labels = get_form_value(body, defaults, "configurations", optional=True)
    if labels is None:
        return
    if not isinstance(labels, list):
        raise HttpError(400, "configurations must be a list of labels")
    for label in labels:
        nb["metadata"]["labels"][label] = "true"


def set_shm(nb: dict, body: dict, defaults: dict) -> None:
    if not get_form_value(body, defaults, "shm", optional=True):
        return
    _pod_spec(nb)["volumes"].append(
        {"name": "dshm", "emptyDir": {"medium": "Memory"}}
    )
    _container(nb)["volumeMounts"].append(
        {"mountPath": "/dev/shm", "name": "dshm"}
    )


def set_environment(nb: dict, body: dict, defaults: dict) -> None:
    env = get_form_value(body, defaults, "environment", optional=True) or {}
    if isinstance(env, str):
        import json
        env = json.loads(env) if env else {}
    _container(nb)["env"].extend(
        {"name": k, "value": str(v)} for k, v in env.items()
    )


# ------------------------------------------------------------- volumes

def volume_requests(nb_name: str, body: dict, defaults: dict) -> list[dict]:
    """Workspace + data volumes from the form (reference post.py:41-49).
    Each request: {mount, newPvc} or {mount, existingSource|name}."""
    vols = list(get_form_value(body, defaults, "datavols", "dataVolumes",
                               optional=True) or [])
    workspace = get_form_value(body, defaults, "workspace",
                               "workspaceVolume", optional=True)
    if workspace:
        vols.append(workspace)
    # Template the {notebook-name} placeholder the config uses.
    import copy as _copy
    import json as _json
    out = []
    for vol in vols:
        out.append(_copy.deepcopy(_json.loads(
            _json.dumps(vol).replace("{notebook-name}", nb_name)
        )))
    return out


def new_pvc_from(volume: dict) -> dict | None:
    pvc = volume.get("newPvc")
    if not pvc:
        return None
    pvc = dict(pvc)
    pvc.setdefault("apiVersion", "v1")
    pvc.setdefault("kind", "PersistentVolumeClaim")
    return pvc


def attach_volume(nb: dict, volume: dict, pvc_name: str) -> None:
    vol_name = pvc_name
    _pod_spec(nb)["volumes"].append({
        "name": vol_name,
        "persistentVolumeClaim": {"claimName": pvc_name},
    })
    _container(nb)["volumeMounts"].append({
        "name": vol_name,
        "mountPath": volume.get("mount", f"/mnt/{vol_name}"),
    })
